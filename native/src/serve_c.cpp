// Native C serving ABI: config create/parse, model build, weight load,
// request registration and generate — the surface that lets a
// NON-PYTHON host embed the whole serving system, like the reference's
// C API does for its C++ mains (reference src/c/flexflow_c.cc;
// flexflow_model_generate at :1584 driven by
// inference/incr_decoding/incr_decoding.cc:118).
//
// Architecture: the runtime here is Python+XLA (the role Legion plays in
// the reference), so this library embeds CPython and drives the flat
// functions in flexflow_tpu/serve/capi_host.py. The C host never sees a
// PyObject type — handles are opaque void*, errors surface through
// ffsv_last_error(). Single-threaded host assumed (the embedded
// interpreter runs on the caller's thread; the reference's C API is
// likewise not thread-safe per handle).
//
// Build (separate from libflexflow_tpu_native.so, which stays
// python-free since Python loads it via ctypes):
//   g++ -shared -fPIC serve_c.cpp $(python3-config --includes)
//       -L$(libdir) -lpython3.12 -o libflexflow_tpu_serve.so

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#include "flexflow_tpu_c.h"

namespace {

std::string g_error;
PyObject *g_host = nullptr;  // the capi_host module

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_error = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) g_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject *call(const char *fn, PyObject *args) {
  // args: a NEW reference to a tuple (stolen here), or nullptr for ().
  if (!g_host) {
    // checked FIRST: before ffsv_init there may be no interpreter, and
    // PyErr_Occurred without a thread state would crash
    g_error = "ffsv_init not called";
    Py_XDECREF(args);
    return nullptr;
  }
  // A nullptr WITH a pending exception means the caller's Py_BuildValue
  // failed (e.g. non-UTF-8 text) — surface that error instead of
  // invoking the function zero-arg under a pending exception.
  if (!args && PyErr_Occurred()) {
    set_error_from_python();
    return nullptr;
  }
  PyObject *f = PyObject_GetAttrString(g_host, fn);
  if (!f) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject *a = args ? args : PyTuple_New(0);
  PyObject *r = PyObject_CallObject(f, a);
  Py_DECREF(f);
  Py_DECREF(a);
  if (!r) set_error_from_python();
  return r;
}

}  // namespace

extern "C" {

const char *ffsv_last_error(void) { return g_error.c_str(); }

/* Initialize the embedded runtime. repo_root: directory containing the
 * flexflow_tpu package (prepended to sys.path; pass NULL if the package
 * is already importable). Returns 0 on success. */
int ffsv_init(const char *repo_root) {
  if (g_host) return 0;
  if (!Py_IsInitialized()) Py_Initialize();
  if (repo_root && *repo_root) {
    PyObject *sys_path = PySys_GetObject("path");  // borrowed
    PyObject *p = PyUnicode_FromString(repo_root);
    if (sys_path && p) PyList_Insert(sys_path, 0, p);
    Py_XDECREF(p);
  }
  g_host = PyImport_ImportModule("flexflow_tpu.serve.capi_host");
  if (!g_host) {
    set_error_from_python();
    return -1;
  }
  /* Embedded-host-only setup (JAX_PLATFORMS override) runs HERE, not at
   * module import: ordinary Python importers of capi_host must not have
   * their session's backend mutated as a side effect. */
  PyObject *r = call("host_init", nullptr);
  if (!r) {
    Py_CLEAR(g_host);
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

/* Tear down handles (the interpreter stays up: XLA backends do not
 * survive re-initialization). */
void ffsv_release(void *handle) { Py_XDECREF((PyObject *)handle); }

void *ffsv_config_create(void) { return call("config_create", nullptr); }

/* Reference flexflow_config_parse_args: argv of reference-style flags. */
void *ffsv_config_parse_args(int argc, const char **argv) {
  if (!g_host) {
    g_error = "ffsv_init not called";
    return nullptr;
  }
  PyObject *lst = PyList_New(argc);
  if (!lst) {
    set_error_from_python();
    return nullptr;
  }
  for (int i = 0; i < argc; i++) {
    PyObject *s = PyUnicode_FromString(argv[i]);
    if (!s) {
      /* non-UTF-8 argv: a NULL element would make the later tuple
       * conversion/call segfault the embedding host — fail loudly with
       * ffsv_last_error set instead (ADVICE r5) */
      set_error_from_python();
      Py_DECREF(lst);
      return nullptr;
    }
    PyList_SetItem(lst, i, s);
  }
  return call("config_parse_args", Py_BuildValue("(N)", lst));
}

int ffsv_config_set(void *cfg, const char *key, const char *value) {
  PyObject *r = call("config_set",
                     Py_BuildValue("(Oss)", (PyObject *)cfg, key, value));
  if (!r) return -1;
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)v;
}

/* Returns a malloc'd string the caller frees, or NULL. */
char *ffsv_config_get(void *cfg, const char *key) {
  PyObject *r = call("config_get",
                     Py_BuildValue("(Os)", (PyObject *)cfg, key));
  if (!r) return nullptr;
  const char *c = PyUnicode_AsUTF8(r);
  char *out = c ? strdup(c) : nullptr;
  Py_DECREF(r);
  return out;
}

/* Build + compile a serving model from the JSON spec documented in
 * capi_host.llm_create (family, model_config, mode, weights_npz,
 * generation_config — the optional adaptive-speculation policy object;
 * see flexflow_tpu_c.h for the key set). */
void *ffsv_llm_create(void *cfg, const char *spec_json) {
  return call("llm_create",
              Py_BuildValue("(Os)", (PyObject *)cfg, spec_json));
}

/* Register a tokenized prompt; returns the request guid or -1. */
long ffsv_register_request(void *llm, const int32_t *tokens, int n_tokens,
                           int max_new_tokens) {
  PyObject *lst = PyList_New(n_tokens);
  for (int i = 0; i < n_tokens; i++)
    PyList_SetItem(lst, i, PyLong_FromLong(tokens[i]));
  PyObject *r = call("register_request",
                     Py_BuildValue("(ONi)", (PyObject *)llm, lst,
                                   max_new_tokens));
  if (!r) return -1;
  long guid = PyLong_AsLong(r);
  Py_DECREF(r);
  return guid;
}

/* Register a tokenized prompt with a per-request wall-clock timeout
 * (seconds; <= 0 = none). Past the deadline the request is cancelled
 * between decode rounds and resolves as timed_out with its partial
 * output. Returns the request guid or -1. */
long ffsv_register_request_timeout(void *llm, const int32_t *tokens,
                                   int n_tokens, int max_new_tokens,
                                   double timeout_s) {
  PyObject *lst = PyList_New(n_tokens);
  for (int i = 0; i < n_tokens; i++)
    PyList_SetItem(lst, i, PyLong_FromLong(tokens[i]));
  PyObject *r = call("register_request_timeout",
                     Py_BuildValue("(ONid)", (PyObject *)llm, lst,
                                   max_new_tokens, timeout_s));
  if (!r) return -1;
  long guid = PyLong_AsLong(r);
  Py_DECREF(r);
  return guid;
}

/* Flag a registered request for cancellation; the next generate round
 * reaps it (slot freed, partial output kept, status -> cancelled).
 * Returns 1 if cancelled, 0 if unknown/finished, -1 on error. */
int ffsv_request_cancel(void *llm, long guid) {
  PyObject *r = call("request_cancel",
                     Py_BuildValue("(Ol)", (PyObject *)llm, guid));
  if (!r) return -1;
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)v;
}

/* Resolution status of a request: -1 unknown, 0 ok, 1 timed_out,
 * 2 cancelled, 3 error, 4 registered-but-unfinished. */
int ffsv_request_status(void *llm, long guid) {
  PyObject *r = call("request_status",
                     Py_BuildValue("(Ol)", (PyObject *)llm, guid));
  if (!r) return -1;
  long v = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)v;
}

/* Build + compile a speculative-decoding pair: verifier (tree-verify
 * mode) + draft SSM(s) (beam-search mode) — the reference's spec_infer
 * main (inference/spec_infer/spec_infer.cc:201). Both specs use the
 * llm_create JSON schema; draft_json may be {"ssms":[spec, ...]} for
 * multi-SSM merged-tree drafting, and the verifier spec's
 * generation_config carries the adaptive-speculation policy (depth
 * bounds, fallback threshold — flexflow_tpu_c.h). Register requests
 * and call ffsv_generate_spec on the returned handle. */
void *ffsv_spec_create(void *cfg, const char *verifier_json,
                       const char *draft_json) {
  return call("spec_create", Py_BuildValue("(Oss)", (PyObject *)cfg,
                                           verifier_json, draft_json));
}

/* Speculative decoding for every pending request. Returns finished
 * count, or -1. spec_depth must be >= 1; generation_config.spec_depth
 * (verifier spec JSON) overrides it when set. */
int ffsv_generate_spec(void *llm, int spec_depth) {
  PyObject *r = call("generate_spec",
                     Py_BuildValue("(Oi)", (PyObject *)llm, spec_depth));
  if (!r) return -1;
  long n = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)n;
}

/* Run incremental decoding for every pending request (reference
 * flexflow_model_generate). Returns finished-request count or -1. */
int ffsv_generate(void *llm) {
  PyObject *r = call("generate", Py_BuildValue("(O)", (PyObject *)llm));
  if (!r) return -1;
  long n = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)n;
}

/* Attach the GPT-2 BPE tokenizer (native C++ when available) so the
 * host takes text prompts — reference flexflow_model_generate's text
 * surface. Returns the vocab size, or -1. */
int ffsv_register_bpe_tokenizer(void *llm, const char *vocab_json_path,
                                const char *merges_path) {
  PyObject *r = call("register_bpe_tokenizer",
                     Py_BuildValue("(Oss)", (PyObject *)llm,
                                   vocab_json_path, merges_path));
  if (!r) return -1;
  long n = PyLong_AsLong(r);
  Py_DECREF(r);
  return (int)n;
}

/* Register a TEXT prompt (requires a registered tokenizer); returns the
 * request guid, or -1. */
long ffsv_register_request_text(void *llm, const char *text,
                                int max_new_tokens) {
  PyObject *r = call("register_request_text",
                     Py_BuildValue("(Osi)", (PyObject *)llm, text,
                                   max_new_tokens));
  if (!r) return -1;
  long guid = PyLong_AsLong(r);
  Py_DECREF(r);
  return guid;
}

/* Decode a finished request's output to text (malloc'd; caller frees),
 * or NULL. */
char *ffsv_get_output_text(void *llm, long guid) {
  PyObject *r = call("get_output_text",
                     Py_BuildValue("(Ol)", (PyObject *)llm, guid));
  if (!r) return nullptr;
  const char *c = PyUnicode_AsUTF8(r);
  char *out = c ? strdup(c) : nullptr;
  Py_DECREF(r);
  return out;
}

/* Snapshot the serving telemetry registry ("json" or "prometheus");
 * malloc'd string the caller frees, or NULL on error. Empty snapshot
 * ("{}" / "") when telemetry is disabled — enable via
 * ffsv_config_set(cfg, "telemetry", "true") before ffsv_llm_create.
 * With a replica fleet live in-process the dump aggregates the global
 * registry plus every replica registry (counters sum, histograms merge
 * bucket-exactly) — see flexflow_tpu_c.h for the full contract. */
char *ffsv_metrics_dump(const char *format) {
  PyObject *r = call("metrics_dump",
                     Py_BuildValue("(s)", format ? format : "json"));
  if (!r) return nullptr;
  const char *c = PyUnicode_AsUTF8(r);
  char *out = c ? strdup(c) : nullptr;
  Py_DECREF(r);
  return out;
}

/* Copy a finished request's output tokens into out (cap entries max);
 * returns the token count (may exceed cap; call again with more room)
 * or -1 on error. */
int ffsv_get_output(void *llm, long guid, int32_t *out, int cap) {
  PyObject *r = call("get_output",
                     Py_BuildValue("(Ol)", (PyObject *)llm, guid));
  if (!r) return -1;
  int n = (int)PyList_Size(r);
  for (int i = 0; i < n && i < cap; i++)
    out[i] = (int32_t)PyLong_AsLong(PyList_GetItem(r, i));
  Py_DECREF(r);
  return n;
}

}  // extern "C"
