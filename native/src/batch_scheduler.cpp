// Continuous-batching request scheduler: host-side hot loop in native code.
//
// Capability parity with the slot/bookkeeping core of the reference's
// RequestManager (src/runtime/request_manager.cc: register_new_request,
// prepare_next_batch slot fill + token bookkeeping). The Python
// RequestManager delegates per-step batch assembly and token-feedback
// bookkeeping here; XLA runs the device side. Semantics mirror
// flexflow_tpu/serve/request_manager.py exactly (parity-tested).

#include "../include/flexflow_tpu_c.h"

#include <algorithm>
#include <cstring>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

struct Req {
  int64_t guid = 0;
  std::vector<int32_t> tokens;   // prompt + generated
  int prompt_len = 0;
  int max_new = 0;
  int max_seq_len = 0;           // 0 = unbounded (use scheduler max_seq)
  int cache_depth = 0;           // tokens already in KV cache
  int generated = 0;
  int slot = -1;
  bool finished = false;
};

struct Sched {
  int R = 0;
  int max_seq = 0;
  int64_t eos = -1;
  std::deque<Req *> pending;
  std::vector<Req *> active;                    // size R, nullable
  std::deque<Req *> done;                       // finished, not yet drained
  std::unordered_map<int64_t, Req *> drained;   // popped, awaiting readout

  explicit Sched(int r, int ms, int64_t e) : R(r), max_seq(ms), eos(e) {
    active.assign(R, nullptr);
  }

  ~Sched() {
    for (Req *r : pending) delete r;
    for (Req *r : active)
      if (r) delete r;
    for (Req *r : done) delete r;
    for (auto &kv : drained) delete kv.second;
  }

  int limit_of(const Req *r) const {
    int lim = r->max_seq_len > 0 ? std::min(r->max_seq_len, max_seq) : max_seq;
    return lim;
  }

  // mirror of request_manager.py _finish_if_done
  bool finish_if_done(Req *r) {
    int lim = limit_of(r);
    if ((int)r->tokens.size() > lim) r->tokens.resize(lim);
    if (r->generated >= r->max_new || (int)r->tokens.size() >= lim ||
        (eos >= 0 && r->generated > 0 && r->tokens.back() == (int32_t)eos)) {
      r->finished = true;
    }
    return r->finished;
  }

  int remaining_budget(const Req *r) const {
    int lim = limit_of(r);
    return std::max(1, std::min(r->max_new - r->generated,
                                lim - (int)r->tokens.size()));
  }
};

}  // namespace

extern "C" {

void *ffs_create(int max_requests, int max_seq, int64_t eos_id) {
  return new Sched(max_requests, max_seq, eos_id);
}

void ffs_destroy(void *handle) { delete static_cast<Sched *>(handle); }

void ffs_add_request(void *handle, int64_t guid, const int32_t *tokens,
                     int n_tokens, int max_new, int max_seq_len) {
  auto *s = static_cast<Sched *>(handle);
  Req *r = new Req();
  r->guid = guid;
  r->tokens.assign(tokens, tokens + n_tokens);
  r->prompt_len = n_tokens;
  r->max_new = max_new;
  r->max_seq_len = max_seq_len;
  s->pending.push_back(r);
}

int ffs_has_work(void *handle) {
  auto *s = static_cast<Sched *>(handle);
  if (!s->pending.empty()) return 1;
  for (Req *r : s->active)
    if (r) return 1;
  return 0;
}

int ffs_fill_slots(void *handle) {
  auto *s = static_cast<Sched *>(handle);
  int placed = 0;
  for (int slot = 0; slot < s->R; ++slot) {
    while (s->active[slot] == nullptr && !s->pending.empty()) {
      Req *r = s->pending.front();
      s->pending.pop_front();
      if ((int)r->tokens.size() >= s->limit_of(r)) {
        // no room to generate even one token: reject to done
        r->finished = true;
        s->done.push_back(r);
        continue;
      }
      r->slot = slot;
      s->active[slot] = r;
      ++placed;
    }
  }
  return placed;
}

int ffs_assemble_prefill(void *handle, int chunk, int budget, int Q,
                         int32_t *tokens, int32_t *positions,
                         int32_t *start_pos, int32_t *num_tokens,
                         uint8_t *active) {
  auto *s = static_cast<Sched *>(handle);
  memset(tokens, 0, sizeof(int32_t) * s->R * Q);
  memset(positions, 0, sizeof(int32_t) * s->R * Q);
  memset(start_pos, 0, sizeof(int32_t) * s->R);
  memset(num_tokens, 0, sizeof(int32_t) * s->R);
  memset(active, 0, s->R);
  int rows = 0;
  for (Req *r : s->active) {
    if (!r || r->finished) continue;
    int d = r->cache_depth;
    int npend = (int)r->tokens.size() - d;
    if (npend > 1) {
      int take = std::min({npend - 1, chunk, budget});
      if (take <= 0) continue;
      for (int j = 0; j < take; ++j) {
        tokens[r->slot * Q + j] = r->tokens[d + j];
        positions[r->slot * Q + j] = d + j;
      }
      start_pos[r->slot] = d;
      num_tokens[r->slot] = take;
      active[r->slot] = 1;
      budget -= take;
      r->cache_depth = d + take;
      ++rows;
    }
  }
  return rows;
}

int ffs_assemble_decode(void *handle, int32_t *tok, int32_t *pos,
                        uint8_t *active) {
  auto *s = static_cast<Sched *>(handle);
  memset(tok, 0, sizeof(int32_t) * s->R);
  memset(pos, 0, sizeof(int32_t) * s->R);
  memset(active, 0, s->R);
  int live = 0;
  for (Req *r : s->active) {
    if (!r || r->finished) continue;
    tok[r->slot] = r->tokens.back();
    pos[r->slot] = (int)r->tokens.size() - 1;
    active[r->slot] = 1;
    ++live;
  }
  return live;
}

int ffs_decode_block(void *handle, int max_block) {
  auto *s = static_cast<Sched *>(handle);
  int block = 0;
  int max_pos = -1;
  for (Req *r : s->active) {
    if (!r || r->finished) continue;
    block = std::max(block, s->remaining_budget(r));
    max_pos = std::max(max_pos, (int)r->tokens.size() - 1);
  }
  if (max_pos < 0) return 0;
  block = std::min(block, max_block);
  block = std::min(block, s->max_seq - 1 - max_pos);
  return std::max(1, block);
}

int ffs_append_block(void *handle, const int32_t *toks, int B) {
  auto *s = static_cast<Sched *>(handle);
  int finished = 0;
  for (int slot = 0; slot < s->R; ++slot) {
    Req *r = s->active[slot];
    if (!r || r->finished) continue;
    for (int j = 0; j < B; ++j) {
      r->tokens.push_back(toks[slot * B + j]);
      r->generated += 1;
      if (s->finish_if_done(r)) break;
    }
    r->cache_depth = (int)r->tokens.size() - 1;
    if (r->finished) {
      s->done.push_back(r);
      s->active[slot] = nullptr;
      ++finished;
    }
  }
  return finished;
}

int ffs_cancel(void *handle, int64_t guid) {
  auto *s = static_cast<Sched *>(handle);
  for (auto it = s->pending.begin(); it != s->pending.end(); ++it) {
    if ((*it)->guid == guid) {
      Req *r = *it;
      s->pending.erase(it);
      r->finished = true;
      s->done.push_back(r);
      return 1;
    }
  }
  for (int slot = 0; slot < s->R; ++slot) {
    Req *r = s->active[slot];
    if (r && r->guid == guid && !r->finished) {
      r->finished = true;
      s->done.push_back(r);
      s->active[slot] = nullptr;
      return 1;
    }
  }
  return 0;
}

int ffs_pop_done(void *handle, int64_t *guid, int32_t *n_tokens) {
  auto *s = static_cast<Sched *>(handle);
  if (s->done.empty()) return 0;
  Req *r = s->done.front();
  s->done.pop_front();
  *guid = r->guid;
  *n_tokens = (int32_t)r->tokens.size();
  s->drained[r->guid] = r;
  return 1;
}

int ffs_done_tokens(void *handle, int64_t guid, int32_t *out, int cap) {
  auto *s = static_cast<Sched *>(handle);
  auto it = s->drained.find(guid);
  if (it == s->drained.end()) return 0;
  Req *r = it->second;
  int n = std::min((int)r->tokens.size(), cap);
  memcpy(out, r->tokens.data(), n * sizeof(int32_t));
  return n;
}

int ffs_prompt_len(void *handle, int64_t guid) {
  auto *s = static_cast<Sched *>(handle);
  auto it = s->drained.find(guid);
  if (it == s->drained.end()) return 0;
  return it->second->prompt_len;
}

}  // extern "C"
