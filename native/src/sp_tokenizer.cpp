// SentencePiece tokenizer (unigram + BPE), dependency-free.
//
// Capability parity with the reference's bundled tokenizers-cpp, which the
// RequestManager selects for LLaMA-family models (reference
// src/runtime/request_manager.cc:109 picks a SentencePiece tokenizer by
// ModelType). Fresh implementation: a minimal protobuf wire-format reader
// for sentencepiece_model.proto (ModelProto{pieces=1{piece=1,score=2,
// type=3}, trainer_spec=2{model_type=3, byte_fallback=35, unk_id=40,
// bos_id=41, eos_id=42}, normalizer_spec=3{add_dummy_prefix=3,
// remove_extra_whitespaces=4, escape_whitespaces=5}}), unigram Viterbi
// segmentation with byte fallback, and greedy score-ordered BPE merging.
// The Python twin in flexflow_tpu/native/sp_tokenizer.py implements the
// same algorithms and is the parity oracle in tests/test_native.py.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// ----------------------------- proto wire -----------------------------
struct Reader {
  const uint8_t *p;
  const uint8_t *end;
  bool ok = true;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
      if (shift > 63) break;
    }
    ok = false;
    return 0;
  }

  bool next(uint32_t *fnum, uint32_t *wtype) {
    if (p >= end || !ok) return false;
    uint64_t key = varint();
    if (!ok) return false;
    *fnum = uint32_t(key >> 3);
    *wtype = uint32_t(key & 7);
    return true;
  }

  // returns a sub-range for length-delimited fields
  Reader sub() {
    uint64_t n = varint();
    // compare against the remaining size, NOT p + n: a corrupt file can
    // carry a near-2^64 length whose pointer addition wraps past the
    // bounds check and walks out of the buffer
    if (!ok || n > uint64_t(end - p)) {
      ok = false;
      return {end, end};
    }
    Reader r{p, p + n};
    p += n;
    return r;
  }

  void skip(uint32_t wtype) {
    switch (wtype) {
      case 0: varint(); break;
      case 1: p += 8; break;
      case 2: sub(); break;
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }

  float f32() {
    if (p + 4 > end) {
      ok = false;
      return 0.f;
    }
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
};

// piece types (sentencepiece_model.proto SentencePiece::Type)
enum PieceType { NORMAL = 1, UNKNOWN = 2, CONTROL = 3, USER_DEFINED = 4,
                 UNUSED = 5, BYTE = 6 };

constexpr const char *kWsPiece = "\xE2\x96\x81";  // U+2581 LOWER ONE EIGHTH
constexpr float kUnkPenalty = 10.0f;

struct SpModel {
  std::vector<std::string> pieces;
  std::vector<float> scores;
  std::vector<int> types;
  std::unordered_map<std::string, int> piece_to_id;
  int model_type = 1;  // 1=UNIGRAM 2=BPE
  bool byte_fallback = false;
  int unk_id = 0, bos_id = 1, eos_id = 2;
  bool add_dummy_prefix = true;
  bool remove_extra_ws = true;
  bool escape_ws = true;
  int byte_id[256];
  float min_score = 0.f;
  size_t max_piece_len = 1;

  void finish() {
    for (int i = 0; i < 256; i++) byte_id[i] = -1;
    min_score = 0.f;
    for (size_t i = 0; i < pieces.size(); i++) {
      piece_to_id.emplace(pieces[i], int(i));
      if (types[i] == NORMAL && scores[i] < min_score) min_score = scores[i];
      if (pieces[i].size() > max_piece_len) max_piece_len = pieces[i].size();
      if (types[i] == BYTE && pieces[i].size() == 6) {
        // "<0xAB>"
        int hi = -1, lo = -1;
        auto hex = [](char c) {
          if (c >= '0' && c <= '9') return c - '0';
          if (c >= 'A' && c <= 'F') return c - 'A' + 10;
          if (c >= 'a' && c <= 'f') return c - 'a' + 10;
          return -1;
        };
        hi = hex(pieces[i][3]);
        lo = hex(pieces[i][4]);
        if (hi >= 0 && lo >= 0) byte_id[hi * 16 + lo] = int(i);
      }
    }
  }
};

bool parse_model(const uint8_t *data, size_t n, SpModel *m) {
  Reader r{data, data + n};
  uint32_t f, w;
  while (r.next(&f, &w)) {
    if (f == 1 && w == 2) {  // pieces
      Reader pr = r.sub();
      std::string piece;
      float score = 0.f;
      int type = NORMAL;
      uint32_t pf, pw;
      while (pr.next(&pf, &pw)) {
        if (pf == 1 && pw == 2) {
          Reader s = pr.sub();
          piece.assign(reinterpret_cast<const char *>(s.p), s.end - s.p);
        } else if (pf == 2 && pw == 5) {
          score = pr.f32();
        } else if (pf == 3 && pw == 0) {
          type = int(pr.varint());
        } else {
          pr.skip(pw);
        }
      }
      m->pieces.push_back(piece);
      m->scores.push_back(score);
      m->types.push_back(type);
    } else if (f == 2 && w == 2) {  // trainer_spec
      Reader tr = r.sub();
      uint32_t tf, tw;
      while (tr.next(&tf, &tw)) {
        if (tf == 3 && tw == 0) m->model_type = int(tr.varint());
        else if (tf == 35 && tw == 0) m->byte_fallback = tr.varint() != 0;
        else if (tf == 40 && tw == 0) m->unk_id = int(tr.varint());
        else if (tf == 41 && tw == 0) m->bos_id = int(tr.varint());
        else if (tf == 42 && tw == 0) m->eos_id = int(tr.varint());
        else tr.skip(tw);
      }
    } else if (f == 3 && w == 2) {  // normalizer_spec
      Reader nr = r.sub();
      uint32_t nf, nw;
      while (nr.next(&nf, &nw)) {
        if (nf == 3 && nw == 0) m->add_dummy_prefix = nr.varint() != 0;
        else if (nf == 4 && nw == 0) m->remove_extra_ws = nr.varint() != 0;
        else if (nf == 5 && nw == 0) m->escape_ws = nr.varint() != 0;
        else nr.skip(nw);
      }
    } else {
      r.skip(w);
    }
  }
  if (!r.ok || m->pieces.empty()) return false;
  m->finish();
  return true;
}

// --------------------------- normalization ----------------------------
std::string normalize(const SpModel &m, const std::string &in) {
  std::string s = in;
  if (m.remove_extra_ws) {
    std::string t;
    size_t a = 0, b = s.size();
    while (a < b && s[a] == ' ') a++;
    while (b > a && s[b - 1] == ' ') b--;
    bool prev_ws = false;
    for (size_t i = a; i < b; i++) {
      if (s[i] == ' ') {
        if (!prev_ws) t.push_back(' ');
        prev_ws = true;
      } else {
        t.push_back(s[i]);
        prev_ws = false;
      }
    }
    s = t;
  }
  if (m.add_dummy_prefix) s = " " + s;
  if (m.escape_ws) {
    std::string t;
    for (char c : s) {
      if (c == ' ') t += kWsPiece;
      else t.push_back(c);
    }
    s = t;
  }
  return s;
}

size_t utf8_len(uint8_t b) {
  if (b < 0x80) return 1;
  if ((b & 0xE0) == 0xC0) return 2;
  if ((b & 0xF0) == 0xE0) return 3;
  if ((b & 0xF8) == 0xF0) return 4;
  return 1;  // invalid byte: treat as one unit
}

void emit_with_fallback(const SpModel &m, const std::string &seg,
                        std::vector<int32_t> *out) {
  if (m.byte_fallback) {
    bool all = true;
    for (unsigned char c : seg)
      if (m.byte_id[c] < 0) all = false;
    if (all) {
      for (unsigned char c : seg) out->push_back(m.byte_id[c]);
      return;
    }
  }
  out->push_back(m.unk_id);
}

// --------------------------- unigram Viterbi ---------------------------
void encode_unigram(const SpModel &m, const std::string &s,
                    std::vector<int32_t> *out) {
  size_t n = s.size();
  if (n == 0) return;
  // char boundaries
  std::vector<size_t> starts;
  std::vector<char> is_start(n + 1, 0);
  for (size_t i = 0; i < n;) {
    starts.push_back(i);
    is_start[i] = 1;
    i += utf8_len(uint8_t(s[i]));
  }
  is_start[n] = 1;
  const float NEG = -1e30f;
  std::vector<float> best(n + 1, NEG);
  std::vector<int> prev(n + 1, -1);     // previous boundary
  std::vector<int> piece(n + 1, -1);    // piece id ending here (-2 => unk)
  best[0] = 0.f;
  float unk_score = m.min_score - kUnkPenalty;
  for (size_t i = 0; i <= n; i++) {
    if (!is_start[i] || best[i] <= NEG) continue;
    if (i == n) break;
    size_t cl = utf8_len(uint8_t(s[i]));
    // unk/byte-fallback single char
    size_t ce = i + cl > n ? n : i + cl;
    if (best[i] + unk_score > best[ce]) {
      best[ce] = best[i] + unk_score;
      prev[ce] = int(i);
      piece[ce] = -2;
    }
    size_t maxl = m.max_piece_len;
    for (size_t e = i + 1; e <= n && e - i <= maxl; e++) {
      if (!is_start[e]) continue;
      auto it = m.piece_to_id.find(s.substr(i, e - i));
      if (it == m.piece_to_id.end()) continue;
      int id = it->second;
      if (m.types[id] != NORMAL && m.types[id] != USER_DEFINED) continue;
      float sc = best[i] + m.scores[id];
      if (sc > best[e]) {
        best[e] = sc;
        prev[e] = int(i);
        piece[e] = id;
      }
    }
  }
  // backtrack
  std::vector<std::pair<int, int>> segs;  // (start, piece or -2)
  int cur = int(n);
  while (cur > 0) {
    if (prev[cur] < 0) return;  // unreachable; give up silently
    segs.push_back({prev[cur], piece[cur]});
    cur = prev[cur];
  }
  for (auto it = segs.rbegin(); it != segs.rend(); ++it) {
    int st = it->first, id = it->second;
    if (id >= 0) {
      out->push_back(id);
    } else {
      size_t cl = utf8_len(uint8_t(s[st]));
      emit_with_fallback(m, s.substr(st, cl), out);
    }
  }
}

// ---------------------------- greedy BPE -------------------------------
void encode_bpe(const SpModel &m, const std::string &s,
                std::vector<int32_t> *out) {
  // symbols as [start, end) byte ranges over s
  std::vector<std::pair<size_t, size_t>> sym;
  for (size_t i = 0; i < s.size();) {
    size_t l = utf8_len(uint8_t(s[i]));
    if (i + l > s.size()) l = s.size() - i;
    sym.push_back({i, i + l});
    i += l;
  }
  // iterate: merge the adjacent pair whose concatenation is a known piece
  // with the highest score; leftmost wins ties (sentencepiece bpe_model)
  while (sym.size() > 1) {
    float best_score = -1e30f;
    int best_i = -1;
    for (size_t i = 0; i + 1 < sym.size(); i++) {
      auto it = m.piece_to_id.find(
          s.substr(sym[i].first, sym[i + 1].second - sym[i].first));
      if (it == m.piece_to_id.end()) continue;
      int id = it->second;
      if (m.types[id] != NORMAL && m.types[id] != USER_DEFINED) continue;
      if (m.scores[id] > best_score) {
        best_score = m.scores[id];
        best_i = int(i);
      }
    }
    if (best_i < 0) break;
    sym[best_i].second = sym[best_i + 1].second;
    sym.erase(sym.begin() + best_i + 1);
  }
  for (auto &p : sym) {
    auto it = m.piece_to_id.find(s.substr(p.first, p.second - p.first));
    if (it != m.piece_to_id.end() &&
        (m.types[it->second] == NORMAL ||
         m.types[it->second] == USER_DEFINED)) {
      out->push_back(it->second);
    } else {
      emit_with_fallback(m, s.substr(p.first, p.second - p.first), out);
    }
  }
}

std::string decode_ids(const SpModel &m, const int32_t *ids, int n) {
  std::string out;
  std::string pending_bytes;
  auto flush = [&]() {
    out += pending_bytes;
    pending_bytes.clear();
  };
  for (int i = 0; i < n; i++) {
    int id = ids[i];
    if (id < 0 || size_t(id) >= m.pieces.size()) continue;
    int t = m.types[id];
    if (t == BYTE) {
      const std::string &p = m.pieces[id];
      int hi = 0, lo = 0;
      auto hex = [](char c) {
        if (c >= '0' && c <= '9') return c - '0';
        if (c >= 'A' && c <= 'F') return c - 'A' + 10;
        if (c >= 'a' && c <= 'f') return c - 'a' + 10;
        return 0;
      };
      if (p.size() == 6) {
        hi = hex(p[3]);
        lo = hex(p[4]);
        pending_bytes.push_back(char(hi * 16 + lo));
      }
      continue;
    }
    flush();
    if (t == CONTROL || t == UNUSED) continue;
    if (t == UNKNOWN) {
      out += " \xE2\x81\x87 ";  // sentencepiece's default unk surface
      continue;
    }
    out += m.pieces[id];
  }
  flush();
  // unescape whitespace
  std::string res;
  if (m.escape_ws) {
    for (size_t i = 0; i < out.size();) {
      if (out.compare(i, 3, kWsPiece) == 0) {
        res.push_back(' ');
        i += 3;
      } else {
        res.push_back(out[i]);
        i += 1;
      }
    }
  } else {
    res = out;
  }
  if (m.add_dummy_prefix && !res.empty() && res[0] == ' ')
    res.erase(res.begin());
  return res;
}

}  // namespace

// ------------------------------- C API ---------------------------------
extern "C" {

void *ffsp_create_from_buffer(const uint8_t *data, int n) {
  auto *m = new SpModel();
  if (!parse_model(data, size_t(n), m)) {
    delete m;
    return nullptr;
  }
  return m;
}

void *ffsp_create(const char *path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return nullptr;
  std::string buf((std::istreambuf_iterator<char>(f)),
                  std::istreambuf_iterator<char>());
  return ffsp_create_from_buffer(
      reinterpret_cast<const uint8_t *>(buf.data()), int(buf.size()));
}

void ffsp_destroy(void *h) { delete static_cast<SpModel *>(h); }

int ffsp_vocab_size(void *h) {
  return int(static_cast<SpModel *>(h)->pieces.size());
}

int ffsp_model_type(void *h) {
  return static_cast<SpModel *>(h)->model_type;
}

int ffsp_bos_id(void *h) { return static_cast<SpModel *>(h)->bos_id; }
int ffsp_eos_id(void *h) { return static_cast<SpModel *>(h)->eos_id; }
int ffsp_unk_id(void *h) { return static_cast<SpModel *>(h)->unk_id; }

// returns number of ids (<= cap); extra ids are dropped
int ffsp_encode(void *h, const char *text, int text_len, int32_t *out,
                int cap) {
  auto *m = static_cast<SpModel *>(h);
  std::string norm = normalize(*m, std::string(text, size_t(text_len)));
  std::vector<int32_t> ids;
  if (m->model_type == 2) encode_bpe(*m, norm, &ids);
  else encode_unigram(*m, norm, &ids);
  int n = int(ids.size() < size_t(cap) ? ids.size() : size_t(cap));
  std::memcpy(out, ids.data(), size_t(n) * sizeof(int32_t));
  return int(ids.size());
}

// returns number of bytes written (<= cap); output NOT nul-terminated
int ffsp_decode(void *h, const int32_t *ids, int n, char *out, int cap) {
  auto *m = static_cast<SpModel *>(h);
  std::string s = decode_ids(*m, ids, n);
  int w = int(s.size() < size_t(cap) ? s.size() : size_t(cap));
  std::memcpy(out, s.data(), size_t(w));
  return int(s.size());
}

int ffsp_piece_to_id(void *h, const char *piece) {
  auto *m = static_cast<SpModel *>(h);
  auto it = m->piece_to_id.find(piece);
  return it == m->piece_to_id.end() ? -1 : it->second;
}

}  // extern "C"
