/* Flat C API for the native runtime components of flexflow_tpu.
 *
 * Capability parity with the reference's native layer: the GPT-2 byte-level
 * BPE tokenizer (reference src/runtime/gpt_tokenizer.cc, 324 LoC) and the
 * continuous-batching request scheduler's host-side hot loop (reference
 * src/runtime/request_manager.cc slot fill / batch assembly). The Python
 * runtime binds these via ctypes (reference used a cffi C API,
 * src/c/flexflow_c.cc); device compute stays in XLA/Pallas.
 */

#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---------------- GPT-2 byte-level BPE tokenizer ---------------- */

/* Create from vocab.json ({"token": id, ...}) and merges.txt file paths.
 * Returns NULL on error. */
void *ffbpe_create(const char *vocab_json_path, const char *merges_path);

/* Create from in-memory buffers (NUL-terminated). */
void *ffbpe_create_from_buffers(const char *vocab_json, const char *merges);

void ffbpe_destroy(void *handle);

int ffbpe_vocab_size(void *handle);

/* Encode UTF-8 text (explicit length — embedded NULs are data, not
 * terminators) into ids. Returns the number of ids produced, or a negative
 * value whose magnitude is the required capacity if cap is too small. */
int ffbpe_encode(void *handle, const char *text, int text_len,
                 int32_t *out_ids, int cap);

/* Decode ids to UTF-8. Returns bytes written (excluding NUL), or negative
 * required capacity. */
int ffbpe_decode(void *handle, const int32_t *ids, int n, char *out, int cap);

/* ---------------- continuous-batching scheduler ---------------- */

/* Create a scheduler with R request slots, a max KV length of max_seq and
 * an optional EOS id (pass -1 for none). */
void *ffs_create(int max_requests, int max_seq, int64_t eos_id);

void ffs_destroy(void *handle);

/* Queue a request. tokens are the prompt; max_new bounds generation;
 * max_seq_len (0 = no per-request bound) caps prompt+generation. */
void ffs_add_request(void *handle, int64_t guid, const int32_t *tokens,
                     int n_tokens, int max_new, int max_seq_len);

/* Non-zero while any request is pending or active. */
int ffs_has_work(void *handle);

/* Move pending requests into free slots. Over-long prompts (no room to
 * generate a single token) are rejected straight to the done queue.
 * Returns the number of requests newly placed in slots. */
int ffs_fill_slots(void *handle);

/* Assemble a prefill batch: for every active slot with >1 pending
 * (uncached) prompt tokens, emit up to `chunk` of them (leaving >=1 pending
 * so the final chunk produces the first generated token), bounded by a
 * total token budget. Writes [R x Q] tokens/positions and per-slot
 * start/num/active arrays, advances each slot's cache depth, and returns
 * the number of rows emitted (0 = no prefill work; proceed to decode). */
int ffs_assemble_prefill(void *handle, int chunk, int budget, int Q,
                         int32_t *tokens, int32_t *positions,
                         int32_t *start_pos, int32_t *num_tokens,
                         uint8_t *active);

/* Assemble a decode step: per live slot the last token and its position.
 * Returns the number of live slots. */
int ffs_assemble_decode(void *handle, int32_t *tok, int32_t *pos,
                        uint8_t *active);

/* Largest safe fused-decode block size: min over live slots of remaining
 * generation budget, clamped to max_block and to the KV cache end. */
int ffs_decode_block(void *handle, int max_block);

/* Feed back a [R x B] block of sampled tokens after a fused decode. Applies
 * EOS/length termination per slot, frees finished slots to the done queue.
 * Returns the number of requests finished by this block. */
int ffs_append_block(void *handle, const int32_t *toks, int B);

/* Cancel a request by guid: a pending request is moved straight to the
 * done queue; an active one is finished in place and its slot freed.
 * Partial tokens (prompt + whatever was generated) stay readable via
 * ffs_pop_done/ffs_done_tokens. Returns 1 if the request was found and
 * cancelled, 0 if unknown or already finished. */
int ffs_cancel(void *handle, int64_t guid);

/* Drain the done queue: returns guid and token count of the next finished
 * request, or 0 if none. */
int ffs_pop_done(void *handle, int64_t *guid, int32_t *n_tokens);

/* Copy the full token sequence (prompt + generated) of a finished request
 * popped by ffs_pop_done. Returns tokens written. Also releases it. */
int ffs_done_tokens(void *handle, int64_t guid, int32_t *out, int cap);

/* Number of prompt tokens for a request (for output splitting). */
int ffs_prompt_len(void *handle, int64_t guid);


/* ---------------- SentencePiece tokenizer (LLaMA family) ----------------
 * Reference: tokenizers-cpp selected by ModelType in
 * request_manager.cc:109; here native/src/sp_tokenizer.cpp. */
void *ffsp_create(const char *model_path);
void *ffsp_create_from_buffer(const uint8_t *data, int n);
void ffsp_destroy(void *handle);
int ffsp_vocab_size(void *handle);
int ffsp_model_type(void *handle);           /* 1=unigram 2=bpe */
int ffsp_bos_id(void *handle);
int ffsp_eos_id(void *handle);
int ffsp_unk_id(void *handle);
int ffsp_encode(void *handle, const char *text, int text_len,
                int32_t *out_ids, int cap);  /* returns total ids */
int ffsp_decode(void *handle, const int32_t *ids, int n, char *out,
                int cap);                    /* returns total bytes */
int ffsp_piece_to_id(void *handle, const char *piece);


/* ---------------- model graph builder ----------------
 * Reference: the model-builder half of the C ABI (src/c/flexflow_c.cc
 * flexflow_model_create + per-op wrappers). A C host constructs the graph
 * and serializes it as the frontend IR (JSON lines); the runtime loads it
 * with flexflow_tpu.torch.model.file_to_ff and compiles/trains. Node ids
 * are >= 0; every function returns a negative value on error. */
void *ffgb_create(void);
void ffgb_destroy(void *handle);
int ffgb_input(void *handle, int index, const char *name);
int ffgb_dense(void *handle, int in, int out_dim, int use_bias,
               const char *name);
int ffgb_conv2d(void *handle, int in, int out_channels, int kh, int kw,
                int sh, int sw, int ph, int pw, int groups, int use_bias,
                const char *name);
int ffgb_pool2d(void *handle, int in, int kh, int kw, int sh, int sw,
                int ph, int pw, int is_max, const char *name);
int ffgb_unary(void *handle, int in, const char *op, const char *name);
int ffgb_binary(void *handle, int a, int b, const char *op,
                const char *name);
int ffgb_concat(void *handle, const int *ins, int n, int axis,
                const char *name);
int ffgb_softmax(void *handle, int in, int axis, const char *name);
int ffgb_dropout(void *handle, int in, double rate, const char *name);
int ffgb_embedding(void *handle, int in, int num_entries, int out_dim,
                   const char *name);
int ffgb_reshape(void *handle, int in, const int *shape, int ndims,
                 const char *name);
/* Normalize over the LAST ndims dims (sizes in normalized_shape). */
int ffgb_layer_norm(void *handle, int in, const int *normalized_shape,
                    int ndims, int affine, double eps, const char *name);
int ffgb_batch_norm(void *handle, int in, const char *name);
/* dim <= 0 -> default (input's last-dim size). */
int ffgb_rms_norm(void *handle, int in, double eps, int dim,
                  const char *name);
/* Training MHA; pass the same id for q/k/v for self-attention. */
int ffgb_multihead_attention(void *handle, int q, int k, int v,
                             int embed_dim, int num_heads, double dropout,
                             const char *name);
/* op: add subtract multiply divide; reverse != 0 -> (scalar OP x). */
int ffgb_scalar(void *handle, int in, const char *op, double scalar,
                int reverse, const char *name);
int ffgb_transpose(void *handle, int in, const int *perm, int ndims,
                   const char *name);
/* Reduction dims must be unique and in [0, FFGB_MAX_DIMS); exact-rank
 * validation happens at IR load. */
#define FFGB_MAX_DIMS 8
int ffgb_mean(void *handle, int in, const int *dims, int ndims,
              int keepdims, const char *name);
/* dtype name per flexflow_tpu.ffconst.DataType values, e.g. "float32". */
int ffgb_cast(void *handle, int in, const char *dtype, const char *name);
int ffgb_output(void *handle, const int *ids, int n);
int ffgb_save(void *handle, const char *path);
int ffgb_serialize(void *handle, char *out, int cap);

/* ---------------- serving ABI (libflexflow_tpu_serve.so) -----------
 * Config create/parse, model build, weight load, request registration
 * and generate — the reference's full-surface C API role
 * (src/c/flexflow_c.cc; flexflow_model_generate :1584), letting a
 * non-Python host run serving end-to-end (the reference's C++ mains,
 * inference/incr_decoding/incr_decoding.cc:118). Implemented in
 * native/src/serve_c.cpp over an embedded CPython runtime (the role
 * Legion plays in the reference); link -lflexflow_tpu_serve AND the
 * matching -lpython3.x. Handles are opaque; release with ffsv_release.
 * Not thread-safe (like the reference C API). */

/* Init the embedded runtime; repo_root = dir containing flexflow_tpu
 * (NULL if already importable). 0 on success. */
int ffsv_init(const char *repo_root);
const char *ffsv_last_error(void);
void ffsv_release(void *handle);

void *ffsv_config_create(void);
/* Reference flexflow_config_parse_args (same flag set as FFConfig.from_args). */
void *ffsv_config_parse_args(int argc, const char **argv);
int ffsv_config_set(void *cfg, const char *key, const char *value);
char *ffsv_config_get(void *cfg, const char *key);   /* caller frees */

/* Build + compile a serving model. spec_json:
 * {"family":"llama|opt|falcon|mpt|starcoder",
 *  "model_config":{...family Config kwargs...},
 *  "mode":"inc|spec|tree", "weights_npz":"path" (optional),
 *  "checkpoint_dir":"path" (optional), "quantize":"int8|int4" (optional),
 *  "generation_config":{...} (optional)}
 *
 * "checkpoint_dir" cold-starts the model from an HF-layout disk
 * checkpoint (config.json + model.safetensors or pytorch_model.bin, as
 * written by flexflow_tpu.models.checkpoint_store): the family and
 * model_config are read from config.json — supplying "model_config" or
 * "weights_npz" alongside it is an error, and an explicit "family" must
 * agree with the checkpoint. "quantize" compresses the weights to int8
 * or int4 on load (quantize-on-load; works with either weight source),
 * token-identical to quantizing the same weights in memory.
 *
 * generation_config keys (all optional; defaults in parentheses) drive
 * the adaptive speculation controller — the same per-request depth
 * tuning + incremental fallback the Python serving stack runs, so an
 * embedded C host's spec decoding never loses to plain decoding:
 *   "adaptive": bool        (true)  controller on/off
 *   "spec_depth": int       (0)     max draft depth; 0 = caller's depth
 *   "min_spec_depth": int   (1)     shrink floor
 *   "fallback_margin": f    (0.95)  park below this est. speedup
 *   "recover_margin": f     (1.05)  un-park above this (hysteresis)
 *   "probe_every": int      (4)     fallback blocks between probe rounds
 *   "ewma_alpha": f         (0.4)   acceptance-EWMA smoothing
 *   "draft_cost_ratio": f   (0)     0 = estimate from parameter bytes
 * plus the shared-prefix KV cache (serve/prefix_cache.py — requests
 * whose prompts share a prefix with an earlier prompt skip those
 * prefill FLOPs; token-identical to the no-reuse path):
 *   "prefix_cache": bool        (false)  arm the refcounted radix pool
 *   "prefix_cache_tokens": int  (0)      pool budget in KV tokens;
 *                                        0 = library default (65536)
 * Unknown keys fail the create (ffsv_last_error) rather than running
 * with silently-defaulted policy. Controller state is observable via
 * ffsv_metrics_dump: ffsv_spec_effective_depth / _fallback_total /
 * _fallback_active / _acceptance_ewma; prefix-cache state via
 * ffsv_prefix_cache_hits_total / _misses_total / _evictions_total,
 * ffsv_prefix_shared_tokens_total and the ffsv_prefix_pool_tokens
 * occupancy gauge. */
void *ffsv_llm_create(void *cfg, const char *spec_json);

/* Speculative-decoding pair: verifier (tree-verify) + draft SSM
 * (beam-search) — the reference's spec_infer main
 * (inference/spec_infer/spec_infer.cc:201). Same JSON schema; the
 * VERIFIER spec's generation_config carries the pair-level adaptive
 * policy. draft_json is either one model spec or {"ssms":[spec, ...]}
 * for multi-SSM drafting (all SSMs propose into one merged token tree
 * per round). */
void *ffsv_spec_create(void *cfg, const char *verifier_json,
                       const char *draft_json);
/* spec_depth: draft-chain depth per round, must be >= 1 (returns -1
 * otherwise; there is no 0-means-default). The verifier spec's
 * generation_config.spec_depth, when set, overrides this argument;
 * with the adaptive controller on (default) the value is the COMPILED
 * maximum and the effective per-request depth adapts below it. */
int ffsv_generate_spec(void *llm, int spec_depth);

/* Register a tokenized prompt; returns the request guid, or -1. When
 * the spec JSON's generation_config sets "timeout_s" > 0, that default
 * wall-clock bound applies to every request registered this way. */
long ffsv_register_request(void *llm, const int32_t *tokens, int n_tokens,
                           int max_new_tokens);
/* Register with an explicit per-request wall-clock timeout (seconds;
 * <= 0 = none, overriding any spec-JSON default). A request past its
 * deadline is cancelled between decode rounds: its slot is freed, the
 * partial output stays readable via ffsv_get_output, and
 * ffsv_request_status reports 1 (timed_out). Returns the guid, or -1. */
long ffsv_register_request_timeout(void *llm, const int32_t *tokens,
                                   int n_tokens, int max_new_tokens,
                                   double timeout_s);
/* Flag a registered request for cancellation; the next
 * ffsv_generate/ffsv_generate_spec round reaps it (slot freed, partial
 * output kept, status -> 2 cancelled). Works on all scheduler paths
 * (incremental python loop, native C++ scheduler, fused speculative).
 * Returns 1 if cancelled, 0 if unknown or already finished, -1 error. */
int ffsv_request_cancel(void *llm, long guid);
/* Resolution status of a request guid: -1 unknown, 0 ok (completed),
 * 1 timed_out, 2 cancelled, 3 error, 4 registered-but-unfinished.
 * Timed-out/cancelled requests still expose their partial tokens via
 * ffsv_get_output / ffsv_get_output_text. */
int ffsv_request_status(void *llm, long guid);
/* Decode every pending request to completion (reference
 * flexflow_model_generate). Returns finished count, or -1. Requests
 * whose deadline expires mid-run, or that were cancelled, count toward
 * the finished total (they RESOLVED — check ffsv_request_status). */
int ffsv_generate(void *llm);
/* Fetch a finished request's output tokens; returns the full count
 * (recall with more room if it exceeds cap), or -1. */
int ffsv_get_output(void *llm, long guid, int32_t *out, int cap);

/* Text surface (reference flexflow_model_generate takes text): attach
 * the GPT-2 BPE tokenizer (returns vocab size or -1), register text
 * prompts, fetch decoded text (malloc'd; caller frees). An unknown or
 * unfinished guid returns NULL (see ffsv_last_error), so empty text is
 * always a real, finished result. */
int ffsv_register_bpe_tokenizer(void *llm, const char *vocab_json_path,
                                const char *merges_path);
long ffsv_register_request_text(void *llm, const char *text,
                                int max_new_tokens);
char *ffsv_get_output_text(void *llm, long guid);

/* Snapshot the serving telemetry registry (flexflow_tpu/telemetry):
 * acceptance/latency histograms, batch occupancy, per-round counters.
 * format: "json" (structured, incl. exact p50/p90/p99 per histogram) or
 * "prometheus" (text exposition). Enable by setting the config field
 * "telemetry" to "true" before ffsv_llm_create (optionally
 * "telemetry_trace_path" for the JSONL span trace); disabled telemetry
 * dumps an empty snapshot ("{}" / "").
 *
 * When the process also runs a replica fleet (FleetTelemetry /
 * ReplicaPool on the Python side), the dump is the AGGREGATE across the
 * global registry plus every live per-replica registry — counters sum,
 * histograms merge bucket-exactly — so one call sees the whole fleet.
 * Per-replica breakdowns (replica="N" labels in prometheus, a
 * "replicas" map in json) are available via FleetTelemetry.snapshot /
 * to_prometheus in-process; the C surface exposes the pooled view.
 * Unknown format strings fail (NULL + ffsv_last_error) rather than
 * guessing. Returns a malloc'd string the caller frees, or NULL on
 * error (see ffsv_last_error). */
char *ffsv_metrics_dump(const char *format);

#ifdef __cplusplus
}
#endif

#endif /* FLEXFLOW_TPU_C_H */
